"""graftlint core: rule registry, suppressions, baseline, and the runner.

The runtime's concurrency invariants (lock discipline, no blocking under
the scheduler lock, deque-only hot queues, frame-handler parity, metric
naming, lazy heavy imports) used to live only in review comments; this
engine turns them into machine-checked rules. Reference analog: the
sanitizer + clang-tidy CI the C++ core of the reference runs — here the
control plane is Python, so the checks are AST-based and repo-native.

Design:
  - a *file rule* sees one parsed module (``FileContext``) and yields
    ``Finding``s;
  - a *project rule* sees every parsed module at once (cross-file
    invariants like protocol-frame parity);
  - per-line ``# graftlint: disable=GL00X`` and file-level
    ``# graftlint: disable-file=GL00X`` comments suppress findings at
    the source, for cases where the code is right and the rule's
    heuristic is not;
  - a checked-in baseline (``baseline.json``) grandfathers findings that
    are intentional, each with a one-line justification. Baseline
    entries match on (rule, file, message) — not line numbers — so they
    survive unrelated edits.

The CLI (``python -m tools.graftlint``) exits non-zero on any finding
that is neither suppressed nor baselined; the tier-1 suite runs it over
``ray_tpu/`` so regressions fail tests, not just style.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Iterable, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+)")
_SUPPRESS_FILE_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([A-Za-z0-9_,\s]+)")


@dataclasses.dataclass
class Finding:
    rule: str
    file: str          # repo-relative path
    line: int
    col: int
    message: str

    def key(self) -> tuple:
        # baseline identity: line numbers drift with unrelated edits, so
        # they are NOT part of it
        return (self.rule, self.file, self.message)

    def render(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class FileContext:
    """One parsed module plus everything rules need: source lines,
    comment map, and suppression directives."""

    def __init__(self, path: str, source: str, relpath: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        for i, ln in enumerate(self.lines, start=1):
            if "graftlint" not in ln:
                continue
            m = _SUPPRESS_FILE_RE.search(ln)
            if m:
                self.file_suppressions.update(
                    r.strip() for r in m.group(1).split(",") if r.strip())
                continue
            m = _SUPPRESS_RE.search(ln)
            if m:
                self.line_suppressions.setdefault(i, set()).update(
                    r.strip() for r in m.group(1).split(",") if r.strip())

    def suppressed(self, f: Finding) -> bool:
        if f.rule in self.file_suppressions or \
                "all" in self.file_suppressions:
            return True
        rules = self.line_suppressions.get(f.line, ())
        return f.rule in rules or "all" in rules

    def comment_on(self, lineno: int) -> str:
        """The comment text on a source line ('' when none). Good enough
        for directive/annotation comments, which never live inside
        strings containing '#' in this codebase."""
        if 1 <= lineno <= len(self.lines):
            ln = self.lines[lineno - 1]
            if "#" in ln:
                return ln[ln.index("#"):]
        return ""

    def statement_comment(self, node: ast.AST) -> str:
        """Comments attached to a (possibly multi-line) statement."""
        end = getattr(node, "end_lineno", node.lineno)
        return " ".join(filter(None, (self.comment_on(i)
                                      for i in range(node.lineno, end + 1))))


# rule registry -------------------------------------------------------- #

FILE_RULES: list[tuple[str, Callable[[FileContext], Iterable[Finding]]]] = []
PROJECT_RULES: list[tuple[str, Callable[[dict], Iterable[Finding]]]] = []


def file_rule(rule_id: str):
    def deco(fn):
        fn.rule_id = rule_id
        FILE_RULES.append((rule_id, fn))
        return fn
    return deco


def project_rule(rule_id: str):
    def deco(fn):
        fn.rule_id = rule_id
        PROJECT_RULES.append((rule_id, fn))
        return fn
    return deco


# running -------------------------------------------------------------- #

def iter_py_files(paths: list[str]) -> list[str]:
    out = []
    for p in paths:
        if not os.path.exists(p):
            # a typo'd path or wrong cwd must not make the gate pass
            # vacuously with "0 findings"
            raise FileNotFoundError(f"graftlint: no such path: {p}")
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def _relpath(path: str, root: str) -> str:
    ap = os.path.abspath(path)
    root = os.path.abspath(root)
    if ap.startswith(root + os.sep):
        return os.path.relpath(ap, root)
    return path


def parse_files(paths: list[str], root: str = REPO_ROOT,
                ) -> tuple[dict[str, FileContext], list[Finding]]:
    ctxs: dict[str, FileContext] = {}
    findings: list[Finding] = []
    for path in iter_py_files(paths):
        rel = _relpath(path, root)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                src = f.read()
            ctxs[rel] = FileContext(path, src, rel)
        except SyntaxError as e:
            findings.append(Finding(
                "GL000", rel, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}"))
    return ctxs, findings


def run_lint(paths: list[str], root: str = REPO_ROOT,
             rules: Optional[set[str]] = None) -> list[Finding]:
    """All unsuppressed findings for `paths` (baseline NOT applied)."""
    from . import rules as _rules  # noqa: F401  (registers on import)
    ctxs, findings = parse_files(paths, root)
    for rule_id, fn in FILE_RULES:
        if rules is not None and rule_id not in rules:
            continue
        for ctx in ctxs.values():
            findings.extend(fn(ctx))
    for rule_id, fn in PROJECT_RULES:
        if rules is not None and rule_id not in rules:
            continue
        findings.extend(fn(ctxs))
    out = []
    for f in findings:
        ctx = ctxs.get(f.file)
        if ctx is not None and ctx.suppressed(f):
            continue
        out.append(f)
    out.sort(key=lambda f: (f.file, f.line, f.rule))
    return out


def lint_source(source: str, filename: str = "snippet.py",
                rules: Optional[set[str]] = None) -> list[Finding]:
    """Lint an in-memory snippet with the file rules (unit-test helper)."""
    from . import rules as _rules  # noqa: F401
    ctx = FileContext(filename, source, filename)
    findings: list[Finding] = []
    for rule_id, fn in FILE_RULES:
        if rules is not None and rule_id not in rules:
            continue
        findings.extend(fn(ctx))
    return [f for f in findings if not ctx.suppressed(f)]


# baseline ------------------------------------------------------------- #

def load_baseline(path: str = DEFAULT_BASELINE) -> list[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    return data.get("findings", [])


def apply_baseline(findings: list[Finding], baseline: list[dict],
                   ) -> tuple[list[Finding], list[dict]]:
    """-> (new findings not in the baseline, stale baseline entries)."""
    keys = {(b["rule"], b["file"], b["message"]) for b in baseline}
    new = [f for f in findings if f.key() not in keys]
    live = {f.key() for f in findings}
    stale = [b for b in baseline
             if (b["rule"], b["file"], b["message"]) not in live]
    return new, stale


def write_baseline(findings: list[Finding], path: str = DEFAULT_BASELINE,
                   prev: Optional[list[dict]] = None) -> None:
    """Write the baseline for the current findings, carrying forward the
    `why` justification of entries that already existed."""
    prev_whys = {(b["rule"], b["file"], b["message"]): b.get("why", "")
                 for b in (prev or [])}
    entries = [{
        "rule": f.rule, "file": f.file, "line": f.line,
        "message": f.message,
        "why": prev_whys.get(f.key(), "TODO: justify or fix"),
    } for f in findings]
    with open(path, "w") as fh:
        json.dump({"comment": "graftlint grandfathered findings; every "
                              "entry needs a one-line `why`. Regenerate "
                              "with --baseline-update (existing whys are "
                              "kept).",
                   "findings": entries}, fh, indent=1, sort_keys=False)
        fh.write("\n")
