"""graftlint rules GL001-GL015.

Each rule encodes an invariant the runtime actually relies on (see the
per-rule docstrings for the motivating subsystem). GL001-GL011 are
lexical/AST-level and intra-procedural: a blocking call hidden behind a
helper method is not traced. The v2 rules (GL012-GL015) close exactly
that gap: they run on the project-wide call graph built by
``callgraph.py`` from the per-module summaries this module emits
(``build_summary``), so a ``*_locked`` contract reached off-lock through
a helper, or a ``time.sleep`` two calls below a frame handler, is now a
finding. Resolution stays conservative — an unresolvable call is a
missing edge, never an error — so the transitive rules under-report
rather than cry wolf; the suppression/baseline machinery absorbs the
residue where the heuristic and the code disagree.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Iterable, Optional

from . import callgraph as _callgraph
from .engine import (Finding, FileContext, file_rule, project_rule)

# --------------------------------------------------------------------- #
# shared helpers
# --------------------------------------------------------------------- #


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c' (None for anything else)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


# a with-statement context expression that acquires a lock, by naming
# convention: with self.lock / with _lock / with w.send_lock / with self.cv
_LOCKISH_RE = re.compile(r"(lock|cv|cond|mutex)$", re.IGNORECASE)
# locks that exist to serialize a pipe/socket write: sending (and the
# pickling Connection.send does) under them is their very purpose
_CONN_LOCK_RE = re.compile(r"(send|sbuf|conn)", re.IGNORECASE)


def _lockish(expr: ast.AST) -> Optional[str]:
    d = dotted(expr)
    if d and _LOCKISH_RE.search(_last_segment(d)):
        return d
    return None


def _is_funcdef(node: ast.AST) -> bool:
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda))


# --------------------------------------------------------------------- #
# GL001 — lock discipline
# --------------------------------------------------------------------- #
# Motivation: Runtime (core/runtime.py) keys its entire object directory,
# refcount, and scheduler state off ONE RLock; helper methods that assume
# the lock is held are named *_locked (the repo's long-standing idiom).
# The rule makes both halves checkable:
#   - an attribute annotated `# guarded by: self.<lock>` at its
#     declaration may only be touched under `with self.<lock>` (or from a
#     *_locked method, whose caller holds it by contract, or __init__);
#   - a call to self.<anything>_locked(...) must itself happen under a
#     class lock or from another *_locked method.

_GUARDED_RE = re.compile(r"guarded by:\s*self\.([A-Za-z_][A-Za-z0-9_]*)")
_LOCK_CTORS = ("Lock", "RLock")


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _collect_class_locks(ctx: FileContext, cls: ast.ClassDef):
    """-> (lock_attrs, cond_aliases {cv_attr: wrapped_lock_attr},
    guarded {attr: lock_attr})."""
    locks: set[str] = set()
    cond: dict[str, str] = {}
    decls: list[tuple[str, ast.AST]] = []
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        if len(targets) != 1:
            continue
        attr = _self_attr(targets[0])
        if attr is None:
            continue
        decls.append((attr, node))
        value = node.value
        if isinstance(value, ast.Call):
            ctor = _last_segment(dotted(value.func))
            if ctor in _LOCK_CTORS:
                locks.add(attr)
            elif ctor == "Condition":
                locks.add(attr)
                if value.args:
                    wrapped = _self_attr(value.args[0])
                    if wrapped:
                        cond[attr] = wrapped
    guarded: dict[str, str] = {}
    for attr, node in decls:
        if attr in locks or attr in cond:
            continue  # a lock is never "guarded by" anything (itself)
        # `self.x = ...  # guarded by: self.lock` (same line(s), or a
        # pure-comment line directly above the declaration)
        above = ctx.lines[node.lineno - 2].strip() \
            if node.lineno >= 2 else ""
        comment = ctx.statement_comment(node)
        if above.startswith("#"):
            comment += " " + above
        m = _GUARDED_RE.search(comment)
        if m:
            guarded[attr] = m.group(1)
    return locks, cond, guarded


@file_rule("GL001")
def check_lock_discipline(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks, cond, guarded = _collect_class_locks(ctx, cls)
        if not locks and not guarded:
            continue
        lock_names = locks | set(cond)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if "_locked" in meth.name or meth.name == "__init__":
                continue  # caller-holds-the-lock contract / construction

            def walk(node: ast.AST, held: frozenset):
                if _is_funcdef(node):
                    # a nested function runs at an unknown time: check
                    # its body against an EMPTY held set (its own with
                    # blocks still count)
                    body = [node.body] if isinstance(node, ast.Lambda) \
                        else node.body
                    for ch in body:
                        walk(ch, frozenset())
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new = set(held)
                    for item in node.items:
                        walk(item.context_expr, held)
                        attr = _self_attr(item.context_expr)
                        if attr in lock_names:
                            new.add(attr)
                            if attr in cond:
                                new.add(cond[attr])
                    for ch in node.body:
                        walk(ch, frozenset(new))
                    return
                attr = _self_attr(node)
                if attr is not None and attr in guarded and \
                        guarded[attr] not in held:
                    findings.append(Finding(
                        "GL001", ctx.relpath, node.lineno, node.col_offset,
                        f"self.{attr} is declared guarded by "
                        f"self.{guarded[attr]} but is touched in "
                        f"{cls.name}.{meth.name} without holding it"))
                if isinstance(node, ast.Call):
                    cattr = _self_attr(node.func)
                    if cattr and "_locked" in cattr and \
                            lock_names and not (held & lock_names):
                        findings.append(Finding(
                            "GL001", ctx.relpath, node.lineno,
                            node.col_offset,
                            f"self.{cattr}() (caller-holds-lock contract)"
                            f" called from {cls.name}.{meth.name} without"
                            f" a class lock held"))
                for ch in ast.iter_child_nodes(node):
                    walk(ch, held)

            for stmt in meth.body:
                walk(stmt, frozenset())
    return findings


# --------------------------------------------------------------------- #
# GL002 — blocking call while holding a lock
# --------------------------------------------------------------------- #
# Motivation: PR 3's combining-lock flush drain had to be designed so no
# sleep/subprocess/join ever happens while the scheduler or a connection
# lock is held — one blocked holder stalls every other sender/scheduling
# pass. Conn-style locks (send_lock/_sbuf_lock) exist to serialize pipe
# writes, so sends and the pickling inside Connection.send are allowed
# under them; everything else on the ban list is not.

_GL002_BANNED_DOTTED = {
    "time.sleep": "time.sleep",
    "sleep": "time.sleep",          # from time import sleep
    "subprocess.run": "subprocess.run",
    "subprocess.Popen": "subprocess.Popen",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "os.system": "os.system",
    "os.waitpid": "os.waitpid",
}
_GL002_PICKLE = {"pickle.dumps", "pickle.loads", "cloudpickle.dumps",
                 "cloudpickle.loads"}
_SENDY = {"send", "sendall", "sendmsg", "send_bytes"}
_CONN_RECV = {"recv", "recv_bytes", "accept"}


def _conn_receiver(func: ast.Attribute) -> bool:
    seg = _last_segment(dotted(func.value)) if dotted(func.value) else ""
    return seg in ("conn", "sock", "socket", "connection") or \
        seg.endswith("_conn") or seg.endswith("_sock")


def _cv_receiver(func: ast.Attribute) -> bool:
    seg = _last_segment(dotted(func.value)) if dotted(func.value) else ""
    return "cv" in seg or "cond" in seg


def _gl002_check_call(node: ast.Call, conn_only: bool) -> Optional[str]:
    """Why this call must not run under the held lock(s), or None."""
    d = dotted(node.func)
    if d is not None:
        if d in _GL002_BANNED_DOTTED:
            return f"{_GL002_BANNED_DOTTED[d]}() blocks"
        if _last_segment(d) == "sleep" and "time" in d.split(".")[0]:
            return "time.sleep() blocks"  # import time as _time, etc.
        if not conn_only and d in _GL002_PICKLE:
            return f"{d}() serializes arbitrary payloads"
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        if meth == "join" and not node.args and not node.keywords:
            return ".join() blocks until another thread/process exits"
        if not conn_only:
            if meth == "wait" and not _cv_receiver(node.func):
                return ".wait() parks the holder (only a condition " \
                       "variable's wait releases the lock)"
            if meth in _SENDY and _conn_receiver(node.func):
                return f".{meth}() writes to a pipe/socket (can block " \
                       f"on a full buffer)"
            if meth in _CONN_RECV and _conn_receiver(node.func):
                return f".{meth}() blocks on the peer"
    return None


@file_rule("GL002")
def check_blocking_under_lock(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []

    def walk(node: ast.AST, held: frozenset):
        if _is_funcdef(node):
            body = [node.body] if isinstance(node, ast.Lambda) else node.body
            for ch in body:
                walk(ch, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in node.items:
                walk(item.context_expr, held)
                lk = _lockish(item.context_expr)
                if lk:
                    new.add(lk)
            for ch in node.body:
                walk(ch, frozenset(new))
            return
        if held and isinstance(node, ast.Call):
            conn_only = all(_CONN_LOCK_RE.search(_last_segment(lk))
                            for lk in held)
            why = _gl002_check_call(node, conn_only)
            if why:
                findings.append(Finding(
                    "GL002", ctx.relpath, node.lineno, node.col_offset,
                    f"{why} while holding {', '.join(sorted(held))}"))
        for ch in ast.iter_child_nodes(node):
            walk(ch, held)

    walk(ctx.tree, frozenset())
    return findings


# --------------------------------------------------------------------- #
# GL003 — blocking call inside `async def`
# --------------------------------------------------------------------- #
# Motivation: serve's proxy/handle/multiplex and the OpenAI endpoint run
# on shared asyncio loops; one synchronous sleep or network call stalls
# EVERY in-flight request on that loop (and the local-mode loop guard in
# serve/local_mode.py exists for exactly this failure class).

_GL003_BANNED = {
    "time.sleep": "time.sleep() stalls the event loop; use "
                  "asyncio.sleep()",
    "sleep": "time.sleep() stalls the event loop; use asyncio.sleep()",
    "subprocess.run": "subprocess.run() blocks the loop",
    "subprocess.call": "subprocess.call() blocks the loop",
    "subprocess.check_call": "subprocess.check_call() blocks the loop",
    "subprocess.check_output": "subprocess.check_output() blocks the loop",
    "os.system": "os.system() blocks the loop",
    "urllib.request.urlopen": "urlopen() does blocking I/O on the loop",
    "urlopen": "urlopen() does blocking I/O on the loop",
    "socket.create_connection": "blocking connect on the loop",
}


@file_rule("GL003")
def check_blocking_in_async(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []

    def scan_async(fn: ast.AsyncFunctionDef):
        def walk(node: ast.AST, awaited: bool = False):
            if _is_funcdef(node):
                # nested sync defs may run in an executor; nested ASYNC
                # defs get their own scan from the module walk below
                # (descending here double-reported every finding)
                return
            if isinstance(node, ast.Await):
                walk(node.value, awaited=True)
                return
            if isinstance(node, ast.Call) and not awaited:
                d = dotted(node.func)
                msg = _GL003_BANNED.get(d)
                if msg is None and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "join" and not node.args \
                        and not node.keywords:
                    msg = ".join() blocks the event loop"
                if msg:
                    findings.append(Finding(
                        "GL003", ctx.relpath, node.lineno,
                        node.col_offset,
                        f"{msg} (inside async def {fn.name})"))
            for ch in ast.iter_child_nodes(node):
                walk(ch)
        for stmt in fn.body:
            walk(stmt)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            scan_async(node)
    return findings


# --------------------------------------------------------------------- #
# GL004 — O(n) list ops on hot queues
# --------------------------------------------------------------------- #
# Motivation: PR 2 swept the engine/handle/worker hot queues onto
# collections.deque after list.pop(0) showed up in profiles; this keeps
# the stragglers (and future reintroductions) out. sys.path-style
# prepends are exempt — they are rare, tiny, and order-semantic.

@file_rule("GL004")
def check_hot_queue_ops(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        meth = node.func.attr
        if meth not in ("pop", "insert") or not node.args:
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant) and arg0.value == 0):
            continue
        if meth == "pop" and len(node.args) != 1:
            continue
        if meth == "insert" and len(node.args) != 2:
            continue
        recv = dotted(node.func.value)
        seg = _last_segment(recv).lower() if recv else ""
        if seg in ("path", "paths") or seg.endswith("path") \
                or seg.endswith("paths"):
            continue  # sys.path.insert(0, ...) and friends
        findings.append(Finding(
            "GL004", ctx.relpath, node.lineno, node.col_offset,
            f"{seg or 'list'}.{meth}(0{', ...' if meth == 'insert' else ''}"
            f") is O(n); use collections.deque "
            f"({'popleft' if meth == 'pop' else 'appendleft'})"))
    return findings


# --------------------------------------------------------------------- #
# GL005 — import hygiene (static counterpart of test_no_heavy_imports)
# --------------------------------------------------------------------- #
# Motivation: worker fork/startup cost is dominated by imports (jax alone
# is hundreds of ms); `import ray_tpu` must stay light. The dynamic test
# catches a leak only at runtime — this walks the STATIC top-level import
# closure of ray_tpu/__init__ and flags any heavy import inside it, with
# the exact file:line to fix.

HEAVY_MODULES = {"jax", "jaxlib", "flax", "optax", "aiohttp",
                 "opentelemetry", "torch", "tensorflow", "pandas",
                 "scipy", "sklearn"}
IMPORT_ROOT = "ray_tpu"


def _module_name(relpath: str) -> Optional[str]:
    if not relpath.endswith(".py"):
        return None
    parts = relpath[:-3].replace(os.sep, "/").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def _is_type_checking_if(node: ast.If) -> bool:
    d = dotted(node.test)
    return d in ("TYPE_CHECKING", "typing.TYPE_CHECKING")


def _top_level_imports(tree: ast.Module):
    """Yield Import/ImportFrom nodes that execute at module import time
    (including inside top-level try/if, excluding `if TYPE_CHECKING`)."""
    def scan(body):
        for node in body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                yield node
            elif isinstance(node, ast.Try):
                yield from scan(node.body)
                for h in node.handlers:
                    yield from scan(h.body)
                yield from scan(node.orelse)
                yield from scan(node.finalbody)
            elif isinstance(node, ast.If) and not _is_type_checking_if(node):
                yield from scan(node.body)
                yield from scan(node.orelse)
    yield from scan(tree.body)


@project_rule("GL005")
def check_import_hygiene(summaries: dict[str, dict]) -> Iterable[Finding]:
    # (relpath, top_imports) per in-package module, keyed by dotted name
    modules: dict[str, tuple[str, list]] = {}
    for rel, s in summaries.items():
        name = _module_name(rel)
        if name and (name == IMPORT_ROOT
                     or name.startswith(IMPORT_ROOT + ".")):
            modules[name] = (rel, s["top_imports"])
    if IMPORT_ROOT not in modules:
        return []

    def deps_of(name: str) -> set[str]:
        deps: set[str] = set()

        def add(target: str):
            # importing a.b.c imports a and a.b too (__init__ chain)
            parts = target.split(".")
            for i in range(1, len(parts) + 1):
                cand = ".".join(parts[:i])
                if cand in modules:
                    deps.add(cand)

        rel, imports = modules[name]
        pkg = name if rel.endswith("__init__.py") \
            else name.rsplit(".", 1)[0]
        for rec in imports:
            if rec["kind"] == "import":
                for target in rec["names"]:
                    add(target)
            else:
                if rec["level"]:
                    base_parts = pkg.split(".")
                    up = rec["level"] - 1
                    if up:
                        base_parts = base_parts[:-up] if up < len(
                            base_parts) else []
                    base = ".".join(base_parts)
                else:
                    base = ""
                mod = (base + "." + rec["module"]
                       if base and rec["module"]
                       else (rec["module"] or base))
                if mod:
                    add(mod)
                    for target in rec["names"]:
                        add(mod + "." + target)
        return deps

    # BFS the import closure from the package root
    closure: set[str] = set()
    frontier = [IMPORT_ROOT]
    while frontier:
        name = frontier.pop()
        if name in closure:
            continue
        closure.add(name)
        frontier.extend(deps_of(name) - closure)

    findings: list[Finding] = []
    for name in sorted(closure):
        rel, imports = modules[name]
        for rec in imports:
            roots = []
            if rec["kind"] == "import":
                roots = [t.split(".")[0] for t in rec["names"]]
            elif rec["level"] == 0 and rec["module"]:
                roots = [rec["module"].split(".")[0]]
            for r in roots:
                if r in HEAVY_MODULES:
                    findings.append(Finding(
                        "GL005", rel, rec["lineno"], rec["col"],
                        f"top-level `import {r}` in a module on the "
                        f"eager `import {IMPORT_ROOT}` path; import it "
                        f"lazily inside the function that needs it"))
    return findings


# --------------------------------------------------------------------- #
# GL006 — control-plane frame parity, pinned to PROTOCOL_VERSION
# --------------------------------------------------------------------- #
# Motivation: every `{"t": ...}` frame a peer sends must have a handler
# on the receiving side — a handler-less frame type is silently dropped
# (or worse, poisons a batch). The full frame inventory is additionally
# pinned to PROTOCOL_VERSION via frames.json: changing the wire
# vocabulary without bumping the version (protocol.py's contract) is
# itself a finding. Regenerate the manifest with --update-frames.

FRAME_MODULES = (
    "ray_tpu/core/worker.py",
    "ray_tpu/core/client.py",
    "ray_tpu/core/runtime.py",
    "ray_tpu/core/node_agent.py",
    "ray_tpu/core/flight.py",       # pull_reply builds the flight_ring frame
    "ray_tpu/core/stacks.py",       # dump_reply builds the stack_reply frame
    "ray_tpu/core/directory.py",    # dir_update/dir_query senders (v7)
    "ray_tpu/util/metrics.py",
    "ray_tpu/util/tracing.py",
    "ray_tpu/util/chaos.py",
    "ray_tpu/experimental/device_objects.py",
)
PROTOCOL_FILE = "ray_tpu/core/protocol.py"
FRAMES_MANIFEST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "frames.json")


def _t_ish(node: ast.AST) -> bool:
    """Does this expression read a frame's type tag? t / msg["t"] /
    m.get("t") / reply.get("t")."""
    if isinstance(node, ast.Name) and node.id == "t":
        return True
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == "t"
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "get" and node.args:
        a0 = node.args[0]
        return isinstance(a0, ast.Constant) and a0.value == "t"
    return False


def _collect_frames(ctx: FileContext):
    """-> (sent {type: (line)}, handled {type: line})."""
    sent: dict[str, int] = {}
    handled: dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "t" and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    sent.setdefault(v.value, node.lineno)
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(_t_ish(s) for s in sides):
                continue
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    handled.setdefault(s.value, node.lineno)
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    for el in s.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            handled.setdefault(el.value, node.lineno)
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Dict) and _t_ish(node.slice):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    handled.setdefault(k.value, node.lineno)
    return sent, handled


def _protocol_version(ctx: FileContext) -> Optional[int]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "PROTOCOL_VERSION" and \
                isinstance(node.value, ast.Constant):
            return node.value.value
    return None


def compute_frame_inventory(summaries: dict[str, dict]):
    sent: dict[str, tuple[str, int]] = {}
    handled: dict[str, tuple[str, int]] = {}
    for rel in FRAME_MODULES:
        s = summaries.get(rel)
        if s is None:
            continue
        for ty, line in s["frames_sent"].items():
            sent.setdefault(ty, (rel, line))
        for ty, line in s["frames_handled"].items():
            handled.setdefault(ty, (rel, line))
    return sent, handled


@project_rule("GL006")
def check_frame_parity(summaries: dict[str, dict]) -> Iterable[Finding]:
    present = [rel for rel in FRAME_MODULES if rel in summaries]
    if len(present) < len(FRAME_MODULES):
        return []  # partial-tree lint (unit tests, single files)
    sent, handled = compute_frame_inventory(summaries)
    findings: list[Finding] = []
    for ty in sorted(set(sent) - set(handled)):
        rel, line = sent[ty]
        findings.append(Finding(
            "GL006", rel, line, 0,
            f'frame type "{ty}" is sent but no peer handles it '
            f"(silently dropped on receive)"))
    for ty in sorted(set(handled) - set(sent)):
        rel, line = handled[ty]
        findings.append(Finding(
            "GL006", rel, line, 0,
            f'frame type "{ty}" has a handler but no sender '
            f"(dead handler, or the sender bypasses the scanned "
            f"modules)"))

    # version pinning
    ps = summaries.get(PROTOCOL_FILE)
    pv = ps.get("protocol_version") if ps else None
    frames = sorted(set(sent) | set(handled))
    if pv is not None:
        if not os.path.exists(FRAMES_MANIFEST):
            findings.append(Finding(
                "GL006", PROTOCOL_FILE, 1, 0,
                "frame manifest missing; run `python -m tools.graftlint "
                "--update-frames`"))
        else:
            with open(FRAMES_MANIFEST) as f:
                manifest = json.load(f)
            if manifest.get("frames") != frames:
                if manifest.get("protocol_version") == pv:
                    findings.append(Finding(
                        "GL006", PROTOCOL_FILE, 1, 0,
                        f"wire frame inventory changed but "
                        f"PROTOCOL_VERSION is still {pv}; bump it "
                        f"(core/protocol.py contract) and run "
                        f"`python -m tools.graftlint --update-frames`"))
                else:
                    findings.append(Finding(
                        "GL006", PROTOCOL_FILE, 1, 0,
                        f"PROTOCOL_VERSION is {pv} but the frame "
                        f"manifest was pinned at "
                        f"{manifest.get('protocol_version')}; run "
                        f"`python -m tools.graftlint --update-frames`"))
    return findings


def update_frames_manifest(ctxs: dict[str, FileContext]) -> dict:
    missing = [rel for rel in FRAME_MODULES + (PROTOCOL_FILE,)
               if rel not in ctxs]
    if missing:
        # re-pinning from a subtree would silently shrink the manifest
        # to a partial inventory and break the GL006 gate for everyone
        raise FileNotFoundError(
            "--update-frames needs the full tree (run it over ray_tpu/); "
            "missing: " + ", ".join(missing))
    summaries = {rel: build_summary(ctx) for rel, ctx in ctxs.items()}
    sent, handled = compute_frame_inventory(summaries)
    pctx = ctxs.get(PROTOCOL_FILE)
    pv = _protocol_version(pctx) if pctx else None
    manifest = {"protocol_version": pv,
                "frames": sorted(set(sent) | set(handled))}
    with open(FRAMES_MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    return manifest


# --------------------------------------------------------------------- #
# GL007 — metric naming + once-only registration
# --------------------------------------------------------------------- #
# Motivation: the head merges every process's series by NAME; names
# outside the rtpu_(core|llm|serve|rl|data|obs)_ namespaces silently
# fall off the dashboards and the metrics_summary() aggregations.
# Constructing a Metric per call re-validates against the registry on a
# hot path — construct at module scope or through cached_metric
# (llm/telemetry.py's pattern).

_METRIC_CTORS = ("Counter", "Gauge", "Histogram")
_METRIC_NAME_RE = re.compile(
    r"^rtpu_(core|llm|serve|rl|data|obs)_[a-z0-9_]+$")
_GL007_EXEMPT_FILES = ("ray_tpu/util/metrics.py",)


def _metric_name_arg(node: ast.Call) -> Optional[ast.Constant]:
    fn = _last_segment(dotted(node.func))
    idx = None
    if fn in _METRIC_CTORS:
        idx = 0
    elif fn == "cached_metric":
        idx = 1
    elif fn and any(s in fn.lower()
                    for s in ("metric", "hist", "gauge", "counter")):
        idx = 0
    if idx is None:
        return None
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return kw.value
    if len(node.args) > idx and isinstance(node.args[idx], ast.Constant):
        return node.args[idx]
    return None


@file_rule("GL007")
def check_metric_conventions(ctx: FileContext) -> Iterable[Finding]:
    if ctx.relpath in _GL007_EXEMPT_FILES:
        return []
    findings: list[Finding] = []

    # which Call nodes sit inside a function body?
    in_func: set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for ch in ast.walk(node):
                if isinstance(ch, ast.Call):
                    in_func.add(id(ch))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _last_segment(dotted(node.func))
        name_node = _metric_name_arg(node)
        if name_node is not None and isinstance(name_node.value, str):
            name = name_node.value
            strict = fn in _METRIC_CTORS or fn == "cached_metric"
            if not _METRIC_NAME_RE.match(name) and (
                    strict or name.startswith("rtpu_")):
                findings.append(Finding(
                    "GL007", ctx.relpath, node.lineno, node.col_offset,
                    f'metric name "{name}" does not match '
                    f"rtpu_(core|llm|serve|rl|data|obs)_[a-z0-9_]+"))
        if fn in _METRIC_CTORS and id(node) in in_func:
            findings.append(Finding(
                "GL007", ctx.relpath, node.lineno, node.col_offset,
                f"{fn}(...) constructed inside a function (per-call "
                f"re-registration); construct at module scope or via "
                f"cached_metric()"))
    return findings


# --------------------------------------------------------------------- #
# GL008 — swallowed exceptions
# --------------------------------------------------------------------- #
# Motivation: daemon threads (recv loops, drop loops, flushers) and
# actor loops die silently on an uncaught exception — and live wrongly
# on an over-caught one. A bare `except:` eats KeyboardInterrupt/
# SystemExit (it has stranded worker teardown before); a broad
# `except Exception: pass` with no comment hides bugs from the one
# person who will ever see them: the reader.

_BROAD = ("Exception", "BaseException")


def _is_silent_body(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)):
            continue
        return False
    return True


def _handler_types(node: ast.ExceptHandler) -> list[str]:
    if node.type is None:
        return []
    elts = node.type.elts if isinstance(node.type, ast.Tuple) \
        else [node.type]
    return [_last_segment(dotted(e)) or "?" for e in elts]


# --------------------------------------------------------------------- #
# GL009 — short-slice seal polling where event-driven waits exist
# --------------------------------------------------------------------- #
# Motivation: the native store exposes event-driven seal notification
# (os_wait_sealed multi-oid waits, os_chan_get stop-aware blocking get,
# os_wait_seq) — a futex wake delivers a completion the instant it seals.
# A `while` loop re-issuing `store.get(..., timeout_ms=<short>)` slices,
# or sleeping briefly between `contains()` probes, burns a syscall + GIL
# round-trip per slice and adds up to a slice of latency per message;
# the compiled-DAG channel transport was rebuilt precisely to retire
# this pattern. Long slices (>150ms) that exist to re-check out-of-band
# state (spill files, directory entries, reconnect-swapped stores) are
# NOT flagged — they are the documented fallback cadence, with the futex
# still delivering the fast path.

_GL009_MAX_SLICE_MS = 150
_GL009_MAX_SLEEP_S = 0.25


def _const_num(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    return None


@file_rule("GL009")
def check_seal_polling(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    def loop_calls(loop: ast.While) -> list[ast.Call]:
        """Call nodes executed BY the loop body: nested function/lambda
        bodies run elsewhere, so recurse without descending into them
        (ast.walk can't prune, it flattens everything)."""
        out: list[ast.Call] = []

        def visit(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if _is_funcdef(child):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                visit(child)

        visit(loop)
        return out

    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, ast.While):
            continue
        calls = loop_calls(loop)
        has_contains = any(
            isinstance(c.func, ast.Attribute) and c.func.attr == "contains"
            for c in calls)
        for c in calls:
            if not isinstance(c.func, ast.Attribute):
                continue
            meth = c.func.attr
            if meth == "get":
                for kw in c.keywords:
                    if kw.arg != "timeout_ms":
                        continue
                    v = _const_num(kw.value)
                    if v is not None and 0 < v <= _GL009_MAX_SLICE_MS:
                        findings.append(Finding(
                            "GL009", ctx.relpath, c.lineno, c.col_offset,
                            f"{meth}(timeout_ms={v:g}) retry slice inside "
                            f"a while loop polls for a seal; use "
                            f"wait_sealed / get_chan (futex wakes on "
                            f"seal) and keep only long re-check slices"))
            elif meth == "sleep" and has_contains and c.args:
                v = _const_num(c.args[0])
                if v is not None and 0 < v <= _GL009_MAX_SLEEP_S:
                    findings.append(Finding(
                        "GL009", ctx.relpath, c.lineno, c.col_offset,
                        f"sleep({v:g}) between contains() probes polls "
                        f"for a seal; use wait_sealed (futex wakes on "
                        f"seal) instead of a sleep-probe loop"))
    return findings


# --------------------------------------------------------------------- #
# GL010 — eager formatting/allocation at flight-recorder emit sites
# --------------------------------------------------------------------- #
# Motivation: flight.evt() is budgeted at well under a microsecond so it
# can stay ALWAYS-ON inside the zero-dispatch fast paths (core/flight.py
# docstring). Python evaluates arguments BEFORE the call, so an f-string,
# %-format, .format(), str()/repr() or a dict/list/set literal in evt's
# argument list pays allocation + formatting on every emit even though
# the recorder only stores fixed-width ints — exactly the cost the
# struct-packed ring exists to avoid. Codes resolve to names at export
# time; object ids compress through flight.lo48 (bytes slicing, no
# string rendering).

_GL010_STR_BUILDERS = ("str", "repr", "bytes", "hex", "format")


def _gl010_bad_arg(arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod) and (
            isinstance(arg.left, ast.Constant)
            and isinstance(arg.left.value, str)):
        return "%-formatting"
    if isinstance(arg, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return "container literal"
    if isinstance(arg, ast.Call):
        if isinstance(arg.func, ast.Attribute) and \
                arg.func.attr == "format":
            return ".format() call"
        if isinstance(arg.func, ast.Name) and \
                arg.func.id in _GL010_STR_BUILDERS:
            return f"{arg.func.id}() call"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return "string constant (the ring stores ints; add a code)"
    return None


@file_rule("GL010")
def check_flight_emit_cost(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "evt":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            why = _gl010_bad_arg(arg)
            if why:
                findings.append(Finding(
                    "GL010", ctx.relpath, arg.lineno, arg.col_offset,
                    f"{why} evaluated on the flight-recorder hot path; "
                    f"evt() args must be plain ints (codes + "
                    f"flight.lo48 ids) — formatting belongs at export "
                    f"time"))
    return findings


# --------------------------------------------------------------------- #
# GL011 — unbounded request-controlled TSDB/metric label values
# --------------------------------------------------------------------- #
# Motivation: the metrics plane (ray_tpu/obs) retains one preallocated
# ring PER (name, label-set) series. The TSDB's hard cardinality cap
# folds overflow into an __overflow__ sink, so memory is safe — but a
# record site that mints label values by FORMATTING request-controlled
# data (f"tenant-{tid}", str(request_id), "%s" % route) fills the whole
# series table with one-sample garbage and evicts the real series into
# the sink: the history silently goes blind. Label values must come
# from bounded vocabularies (the admission gate's bucket(), fixed
# enums, config) — bounding belongs at the call site that OWNS the
# vocabulary, not in the store. Flagged: f-string / str()-family /
# %-format / .format() / string-concat VALUES inside a `tags=` dict at
# metric record sites (.inc/.set/.observe) and inside the key tuple of
# TSDB .record() calls. Plain variables pass — the rule catches the
# syntactic act of minting a fresh string per record, which is exactly
# the unbounded case.

_GL011_RECORD_METHODS = ("inc", "set", "observe")


def _gl011_bad_value(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            (isinstance(node.left, ast.JoinedStr) or
             (isinstance(node.left, ast.Constant) and
              isinstance(node.left.value, str))):
        # only string % value is formatting; integer modulo (n % 4) is
        # the bounded-bucketing pattern this rule RECOMMENDS
        return "%-formatting"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        # "pfx" + x (or x + "sfx"): minting a fresh string per record
        if any(isinstance(s, ast.Constant) and isinstance(s.value, str)
               for s in (node.left, node.right)):
            return "string concatenation"
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "format":
            return ".format() call"
        if isinstance(node.func, ast.Name) and \
                node.func.id in _GL010_STR_BUILDERS:
            return f"{node.func.id}() call"
    return None


def _gl011_scan_dict(d: ast.Dict) -> Iterable[tuple[ast.AST, str]]:
    for v in d.values:
        why = _gl011_bad_value(v)
        if why:
            yield v, why


@file_rule("GL011")
def check_unbounded_metric_labels(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        meth = node.func.attr
        if meth in _GL011_RECORD_METHODS:
            for kw in node.keywords:
                if kw.arg != "tags" or not isinstance(kw.value, ast.Dict):
                    continue
                for v, why in _gl011_scan_dict(kw.value):
                    findings.append(Finding(
                        "GL011", ctx.relpath, v.lineno, v.col_offset,
                        f"{why} mints a label value at a metric record "
                        f"site — one fresh string per record grows a "
                        f"TSDB series each; bound the vocabulary at "
                        f"the call site (bucket()/enum/config) before "
                        f"tagging"))
        elif meth == "record":
            # TSDB.record(name, kind, key, ts, value): the key tuple's
            # (k, v) pairs are the label set
            if len(node.args) < 3 or not isinstance(
                    node.args[2], (ast.Tuple, ast.List)):
                continue
            for pair in node.args[2].elts:
                if not isinstance(pair, (ast.Tuple, ast.List)) or \
                        len(pair.elts) != 2:
                    continue
                why = _gl011_bad_value(pair.elts[1])
                if why:
                    findings.append(Finding(
                        "GL011", ctx.relpath, pair.elts[1].lineno,
                        pair.elts[1].col_offset,
                        f"{why} mints a TSDB label value at a "
                        f".record() site — unbounded label sets evict "
                        f"real series into the __overflow__ sink; "
                        f"bound the vocabulary before recording"))
    return findings


@file_rule("GL008")
def check_swallowed_exceptions(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                "GL008", ctx.relpath, node.lineno, node.col_offset,
                "bare `except:` catches KeyboardInterrupt/SystemExit; "
                "use `except Exception` (with a comment) or narrower"))
            continue
        types = _handler_types(node)
        if not any(t in _BROAD for t in types):
            continue
        if not _is_silent_body(node.body):
            continue
        end = max(getattr(s, "end_lineno", s.lineno) for s in node.body)
        has_comment = any(ctx.comment_on(i)
                          for i in range(node.lineno, end + 1))
        if not has_comment:
            findings.append(Finding(
                "GL008", ctx.relpath, node.lineno, node.col_offset,
                f"broad `except {'/'.join(types)}` silently swallowed; "
                f"add a `# why` comment or handle/narrow it"))
    return findings


# --------------------------------------------------------------------- #
# v2: per-module summaries + call-graph project rules (GL012-GL015)
# --------------------------------------------------------------------- #
# The engine caches summaries per file (mtime+sha1), so everything a
# project rule needs must live in this plain-JSON digest — never in the
# parse tree, which a cache hit does not have.


def build_summary(ctx: FileContext) -> dict:
    """The per-module digest the project rules (and the cache) run on."""
    facts = _callgraph.extract_module(ctx.relpath, ctx.tree)
    top_imports = []
    for node in _top_level_imports(ctx.tree):
        if isinstance(node, ast.Import):
            top_imports.append({
                "kind": "import",
                "names": [a.name for a in node.names],
                "lineno": node.lineno, "col": node.col_offset})
        else:
            top_imports.append({
                "kind": "from", "module": node.module or "",
                "level": node.level,
                "names": [a.name for a in node.names],
                "lineno": node.lineno, "col": node.col_offset})
    sent, handled = _collect_frames(ctx)
    classes_with_locks = []
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            locks, _cond, _guarded = _collect_class_locks(ctx, node)
            if locks:
                classes_with_locks.append(node.name)
    return {
        "module_name": facts.module_name,
        "facts": facts.as_dict(),
        "top_imports": top_imports,
        "frames_sent": sent,
        "frames_handled": handled,
        "protocol_version": (_protocol_version(ctx)
                             if ctx.relpath == PROTOCOL_FILE else None),
        "classes_with_locks": classes_with_locks,
        "suppressions": {
            "file": sorted(ctx.file_suppressions),
            "lines": {str(k): sorted(v)
                      for k, v in ctx.line_suppressions.items()}},
    }


def _build_graph(summaries: dict) -> "_callgraph.CallGraph":
    facts = {rel: _callgraph.ModuleFacts.from_dict(s["facts"])
             for rel, s in summaries.items()}
    return _callgraph.CallGraph(facts)


# --------------------------------------------------------------------- #
# GL012 — lock-contract reachability
# --------------------------------------------------------------------- #
# Motivation: the *_locked suffix is this codebase's caller-holds-lock
# contract (GL001 enforces it inside a lock-owning class). What GL001
# structurally cannot see is a *_locked function reached from ANOTHER
# file or from a class that owns no lock — exactly the PR 15
# `_promote_for` bug, where a helper called `_import_payload_locked`
# with no lock anywhere on the stack. The transitive closure works by
# induction: a caller is compliant if it holds a lock at the site or
# carries the contract in its own name, in which case ITS callers are
# checked the same way.


@project_rule("GL012")
def check_lock_contract_reachability(summaries: dict,
                                     ) -> Iterable[Finding]:
    graph = _build_graph(summaries)
    findings: list[Finding] = []
    for rel in sorted(summaries):
        s = summaries[rel]
        locked_classes = set(s["classes_with_locks"])
        for fi in graph.facts[rel].functions:
            # __init__/__del__ run before/after the object is shared, so
            # the lock is not yet (no longer) contended
            caller_ok = fi.locked_contract or \
                fi.name in ("__init__", "__del__")
            if caller_ok:
                continue
            for site in fi.calls:
                if "_locked" not in site.target.rsplit(".", 1)[-1]:
                    continue
                if site.under_lock:
                    continue
                if site.target.startswith("self.") and \
                        fi.cls in locked_classes:
                    continue  # GL001's file-local turf (it sees the
                    #           class's own lock set; we would double-
                    #           report every finding it already has)
                findings.append(Finding(
                    "GL012", rel, site.lineno, site.col,
                    f"`{site.target}()` carries the *_locked "
                    f"caller-holds-lock contract, but `{fi.qualname}` "
                    f"calls it with no lock held and without carrying "
                    f"the contract itself; acquire the lock here, or "
                    f"rename `{fi.qualname}` to `*_locked` so the "
                    f"obligation propagates to its callers"))

    # Part 2 — the dual obligation: a *_locked function EXECUTES with
    # the lock held, so any blocking primitive in (or reachable from)
    # its body blocks every thread contending that lock. GL002 only
    # sees blocking under a syntactic `with <lock>`, which a contract
    # function never has — this is GL002 made transitive. Sites that
    # ARE under a syntactic with-lock are skipped (GL002's turf).
    seen_sites: set = set()
    for rel in sorted(summaries):
        for fi in graph.facts[rel].functions:
            if not fi.locked_contract:
                continue
            for fn, path, blk in graph.reachable_blocking(fi):
                ln, col, why = blk[0], blk[1], blk[2]
                if len(blk) > 3 and blk[3]:
                    continue  # under a syntactic lock: GL002 flags it
                key = (fn.module, ln, col)
                if key in seen_sites:
                    continue
                seen_sites.add(key)
                chain = " -> ".join(p.qualname for p in path)
                findings.append(Finding(
                    "GL012", fn.module, ln, col,
                    f"blocking {why} runs with the lock held by the "
                    f"*_locked contract (via {chain}); move it off the "
                    f"locked path or split the function so the lock "
                    f"drops first"))
    return findings


# --------------------------------------------------------------------- #
# GL013 — blocking-reachability into single-threaded contexts
# --------------------------------------------------------------------- #
# Motivation: GL002/GL003 flag a blocking primitive written directly in
# a frame handler or async def; one helper call hides it. The head
# recv thread (Runtime._recv_loop -> _handle_msg), the node agent and
# worker frame loops, the scheduler pump, and every asyncio handler are
# single-threaded hot paths: one os_wait_sealed two frames down the
# call chain stalls the whole control plane (the PR 13 dashboard bug).
# Entry points:
#   - functions named in a class's _RPC_METHODS tuple (the rpc-pool
#     dispatch surface — a blocked handler eats one of 32 pool threads);
#   - direct resolved callees of any auto-detected frame dispatcher
#     (>=3 frame-tag comparisons: the elif-chain recv loops) — the
#     dispatcher itself is exempt, conn.recv IS its job;
#   - every `async def` (transitive only: depth-0 blocking in an async
#     body is GL003's file-local finding already);
#   - the explicit extras below for pumps the heuristics cannot name.
# Edges never cross pool.submit/Thread(target=...)/run_in_executor —
# those hops move the work OFF the hot thread, which is the sanctioned
# fix this rule is meant to force.

_GL013_EXTRA_ROOTS = (
    ("ray_tpu/core/runtime.py", "Runtime._sched_pump_loop",
     "scheduler pump"),
)


@project_rule("GL013")
def check_blocking_reachability(summaries: dict) -> Iterable[Finding]:
    graph = _build_graph(summaries)
    # (root FuncInfo, context description, kind tag, min call depth)
    roots: list = []
    for rel in sorted(summaries):
        mf = graph.facts[rel]
        rpc = set(mf.rpc_methods)
        for fi in mf.functions:
            if fi.cls is not None and fi.name in rpc:
                roots.append((fi, f"worker-RPC handler `{fi.qualname}` "
                                  f"(_RPC_METHODS pool dispatch)",
                              "rpc", 0))
            if fi.frame_dispatch:
                for callee in graph.direct_callees(fi):
                    if callee.frame_dispatch:
                        # a dispatcher handing the connection to another
                        # dispatch loop (recv_loop -> agent_loop): the
                        # callee's recv IS its job, and its own callees
                        # are enumerated as roots in their own right
                        continue
                    roots.append((callee,
                                  f"frame handler `{callee.qualname}` "
                                  f"(dispatched from `{fi.qualname}`)",
                                  "frame", 0))
            if fi.is_async:
                roots.append((fi, f"async handler `{fi.qualname}` "
                                  f"(event loop)", "async", 1))
    for rel, qual, desc in _GL013_EXTRA_ROOTS:
        fi = graph.funcs.get((rel, qual))
        if fi is not None:
            roots.append((fi, f"{desc} `{fi.qualname}`", "pump", 0))

    findings: list[Finding] = []
    seen: set = set()
    for fi, desc, kind, min_depth in roots:
        for fn, path, blk in graph.reachable_blocking(fi):
            ln, col, why = blk[0], blk[1], blk[2]
            if len(path) - 1 < min_depth:
                continue
            key = (fn.module, ln, col, kind)
            if key in seen:
                continue
            seen.add(key)
            chain = " -> ".join(p.qualname for p in path)
            findings.append(Finding(
                "GL013", fn.module, ln, col,
                f"blocking {why} reachable from {desc} via {chain}; "
                f"move the blocking step onto a pool/executor or make "
                f"it event-driven"))
    return findings


# --------------------------------------------------------------------- #
# GL014 — store-object lifecycle on the exception edge
# --------------------------------------------------------------------- #
# Motivation: a store object created (put/seal/create_raw) inside a
# `try` whose broad handler neither re-raises nor releases is a leak:
# the failure is swallowed, the caller never learns the object exists,
# and nothing ever deletes it (the PR 10 `_fail_actor_locked` and PR 11
# rpc-reply leaks, both found by hand in review). The candidate is
# extracted per-file (callgraph._scan_try_leaks); here the call graph
# gets a veto: if anything the handler calls resolves — transitively —
# to a function that releases store objects, the cleanup is reachable
# and the candidate is dismissed. A `finally:` that releases dismisses
# at extraction time.


@project_rule("GL014")
def check_store_lifecycle(summaries: dict) -> Iterable[Finding]:
    graph = _build_graph(summaries)
    findings: list[Finding] = []
    for rel in sorted(summaries):
        for fi in graph.facts[rel].functions:
            for cand in fi.gl014:
                ln, col, desc, h_ln, h_targets = \
                    cand[0], cand[1], cand[2], cand[3], list(cand[4])
                if graph.releases_reachable(fi, h_targets):
                    continue
                findings.append(Finding(
                    "GL014", rel, ln, col,
                    f"store object created by {desc} inside a try whose "
                    f"broad except (line {h_ln}) neither re-raises nor "
                    f"reaches a release; on failure the object leaks in "
                    f"the store — delete/release it in the handler, "
                    f"re-raise, or move cleanup to a finally"))
    return findings


# --------------------------------------------------------------------- #
# GL015 — cfg flag registry
# --------------------------------------------------------------------- #
# Motivation: core/config.py's Config raises AttributeError on unknown
# flags — but only at RUNTIME, on the code path that reads the typo.
# A misspelled `cfg.prefetch_depht` in a rarely-taken branch ships
# silently. This closes the loop statically: every `cfg.<name>` read
# (through any alias of the singleton, with real lexical scoping so the
# `cfg = PagedEngineConfig(...)` locals in llm/ stay invisible) must
# name a declared Flag.


@project_rule("GL015")
def check_cfg_registry(summaries: dict) -> Iterable[Finding]:
    cfg_s = summaries.get(_callgraph.CONFIG_FILE)
    if cfg_s is None:
        return []  # partial-tree lint (unit tests, single files)
    declared = set(cfg_s["facts"]["flag_decls"])
    if not declared:
        return []
    findings: list[Finding] = []
    for rel in sorted(summaries):
        for read in summaries[rel]["facts"]["cfg_reads"]:
            ln, col, attr = read[0], read[1], read[2]
            if attr in declared:
                continue
            findings.append(Finding(
                "GL015", rel, ln, col,
                f"`cfg.{attr}` is not declared in core/config.py's flag "
                f"registry; an unknown flag raises AttributeError only "
                f"on the branch that reads it — declare "
                f'Flag("{attr}", ...) or fix the name'))
    return findings
