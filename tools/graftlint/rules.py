"""graftlint rules GL001-GL008.

Each rule encodes an invariant the runtime actually relies on (see the
per-rule docstrings for the motivating subsystem). All checks are
lexical/AST-level and intra-procedural: a blocking call hidden behind a
helper method is not traced through the call graph. That keeps the pass
fast and predictable; the suppression/baseline machinery absorbs the
residue where the heuristic and the code disagree.
"""
from __future__ import annotations

import ast
import json
import os
import re
from typing import Iterable, Optional

from .engine import (Finding, FileContext, file_rule, project_rule)

# --------------------------------------------------------------------- #
# shared helpers
# --------------------------------------------------------------------- #


def dotted(node: ast.AST) -> Optional[str]:
    """Render a Name/Attribute chain as 'a.b.c' (None for anything else)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_segment(name: Optional[str]) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


# a with-statement context expression that acquires a lock, by naming
# convention: with self.lock / with _lock / with w.send_lock / with self.cv
_LOCKISH_RE = re.compile(r"(lock|cv|cond|mutex)$", re.IGNORECASE)
# locks that exist to serialize a pipe/socket write: sending (and the
# pickling Connection.send does) under them is their very purpose
_CONN_LOCK_RE = re.compile(r"(send|sbuf|conn)", re.IGNORECASE)


def _lockish(expr: ast.AST) -> Optional[str]:
    d = dotted(expr)
    if d and _LOCKISH_RE.search(_last_segment(d)):
        return d
    return None


def _is_funcdef(node: ast.AST) -> bool:
    return isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda))


# --------------------------------------------------------------------- #
# GL001 — lock discipline
# --------------------------------------------------------------------- #
# Motivation: Runtime (core/runtime.py) keys its entire object directory,
# refcount, and scheduler state off ONE RLock; helper methods that assume
# the lock is held are named *_locked (the repo's long-standing idiom).
# The rule makes both halves checkable:
#   - an attribute annotated `# guarded by: self.<lock>` at its
#     declaration may only be touched under `with self.<lock>` (or from a
#     *_locked method, whose caller holds it by contract, or __init__);
#   - a call to self.<anything>_locked(...) must itself happen under a
#     class lock or from another *_locked method.

_GUARDED_RE = re.compile(r"guarded by:\s*self\.([A-Za-z_][A-Za-z0-9_]*)")
_LOCK_CTORS = ("Lock", "RLock")


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _collect_class_locks(ctx: FileContext, cls: ast.ClassDef):
    """-> (lock_attrs, cond_aliases {cv_attr: wrapped_lock_attr},
    guarded {attr: lock_attr})."""
    locks: set[str] = set()
    cond: dict[str, str] = {}
    decls: list[tuple[str, ast.AST]] = []
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        if len(targets) != 1:
            continue
        attr = _self_attr(targets[0])
        if attr is None:
            continue
        decls.append((attr, node))
        value = node.value
        if isinstance(value, ast.Call):
            ctor = _last_segment(dotted(value.func))
            if ctor in _LOCK_CTORS:
                locks.add(attr)
            elif ctor == "Condition":
                locks.add(attr)
                if value.args:
                    wrapped = _self_attr(value.args[0])
                    if wrapped:
                        cond[attr] = wrapped
    guarded: dict[str, str] = {}
    for attr, node in decls:
        if attr in locks or attr in cond:
            continue  # a lock is never "guarded by" anything (itself)
        # `self.x = ...  # guarded by: self.lock` (same line(s), or a
        # pure-comment line directly above the declaration)
        above = ctx.lines[node.lineno - 2].strip() \
            if node.lineno >= 2 else ""
        comment = ctx.statement_comment(node)
        if above.startswith("#"):
            comment += " " + above
        m = _GUARDED_RE.search(comment)
        if m:
            guarded[attr] = m.group(1)
    return locks, cond, guarded


@file_rule("GL001")
def check_lock_discipline(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks, cond, guarded = _collect_class_locks(ctx, cls)
        if not locks and not guarded:
            continue
        lock_names = locks | set(cond)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if "_locked" in meth.name or meth.name == "__init__":
                continue  # caller-holds-the-lock contract / construction

            def walk(node: ast.AST, held: frozenset):
                if _is_funcdef(node):
                    # a nested function runs at an unknown time: check
                    # its body against an EMPTY held set (its own with
                    # blocks still count)
                    body = [node.body] if isinstance(node, ast.Lambda) \
                        else node.body
                    for ch in body:
                        walk(ch, frozenset())
                    return
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    new = set(held)
                    for item in node.items:
                        walk(item.context_expr, held)
                        attr = _self_attr(item.context_expr)
                        if attr in lock_names:
                            new.add(attr)
                            if attr in cond:
                                new.add(cond[attr])
                    for ch in node.body:
                        walk(ch, frozenset(new))
                    return
                attr = _self_attr(node)
                if attr is not None and attr in guarded and \
                        guarded[attr] not in held:
                    findings.append(Finding(
                        "GL001", ctx.relpath, node.lineno, node.col_offset,
                        f"self.{attr} is declared guarded by "
                        f"self.{guarded[attr]} but is touched in "
                        f"{cls.name}.{meth.name} without holding it"))
                if isinstance(node, ast.Call):
                    cattr = _self_attr(node.func)
                    if cattr and "_locked" in cattr and \
                            lock_names and not (held & lock_names):
                        findings.append(Finding(
                            "GL001", ctx.relpath, node.lineno,
                            node.col_offset,
                            f"self.{cattr}() (caller-holds-lock contract)"
                            f" called from {cls.name}.{meth.name} without"
                            f" a class lock held"))
                for ch in ast.iter_child_nodes(node):
                    walk(ch, held)

            for stmt in meth.body:
                walk(stmt, frozenset())
    return findings


# --------------------------------------------------------------------- #
# GL002 — blocking call while holding a lock
# --------------------------------------------------------------------- #
# Motivation: PR 3's combining-lock flush drain had to be designed so no
# sleep/subprocess/join ever happens while the scheduler or a connection
# lock is held — one blocked holder stalls every other sender/scheduling
# pass. Conn-style locks (send_lock/_sbuf_lock) exist to serialize pipe
# writes, so sends and the pickling inside Connection.send are allowed
# under them; everything else on the ban list is not.

_GL002_BANNED_DOTTED = {
    "time.sleep": "time.sleep",
    "sleep": "time.sleep",          # from time import sleep
    "subprocess.run": "subprocess.run",
    "subprocess.Popen": "subprocess.Popen",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "os.system": "os.system",
    "os.waitpid": "os.waitpid",
}
_GL002_PICKLE = {"pickle.dumps", "pickle.loads", "cloudpickle.dumps",
                 "cloudpickle.loads"}
_SENDY = {"send", "sendall", "sendmsg", "send_bytes"}
_CONN_RECV = {"recv", "recv_bytes", "accept"}


def _conn_receiver(func: ast.Attribute) -> bool:
    seg = _last_segment(dotted(func.value)) if dotted(func.value) else ""
    return seg in ("conn", "sock", "socket", "connection") or \
        seg.endswith("_conn") or seg.endswith("_sock")


def _cv_receiver(func: ast.Attribute) -> bool:
    seg = _last_segment(dotted(func.value)) if dotted(func.value) else ""
    return "cv" in seg or "cond" in seg


def _gl002_check_call(node: ast.Call, conn_only: bool) -> Optional[str]:
    """Why this call must not run under the held lock(s), or None."""
    d = dotted(node.func)
    if d is not None:
        if d in _GL002_BANNED_DOTTED:
            return f"{_GL002_BANNED_DOTTED[d]}() blocks"
        if _last_segment(d) == "sleep" and "time" in d.split(".")[0]:
            return "time.sleep() blocks"  # import time as _time, etc.
        if not conn_only and d in _GL002_PICKLE:
            return f"{d}() serializes arbitrary payloads"
    if isinstance(node.func, ast.Attribute):
        meth = node.func.attr
        if meth == "join" and not node.args and not node.keywords:
            return ".join() blocks until another thread/process exits"
        if not conn_only:
            if meth == "wait" and not _cv_receiver(node.func):
                return ".wait() parks the holder (only a condition " \
                       "variable's wait releases the lock)"
            if meth in _SENDY and _conn_receiver(node.func):
                return f".{meth}() writes to a pipe/socket (can block " \
                       f"on a full buffer)"
            if meth in _CONN_RECV and _conn_receiver(node.func):
                return f".{meth}() blocks on the peer"
    return None


@file_rule("GL002")
def check_blocking_under_lock(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []

    def walk(node: ast.AST, held: frozenset):
        if _is_funcdef(node):
            body = [node.body] if isinstance(node, ast.Lambda) else node.body
            for ch in body:
                walk(ch, frozenset())
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new = set(held)
            for item in node.items:
                walk(item.context_expr, held)
                lk = _lockish(item.context_expr)
                if lk:
                    new.add(lk)
            for ch in node.body:
                walk(ch, frozenset(new))
            return
        if held and isinstance(node, ast.Call):
            conn_only = all(_CONN_LOCK_RE.search(_last_segment(lk))
                            for lk in held)
            why = _gl002_check_call(node, conn_only)
            if why:
                findings.append(Finding(
                    "GL002", ctx.relpath, node.lineno, node.col_offset,
                    f"{why} while holding {', '.join(sorted(held))}"))
        for ch in ast.iter_child_nodes(node):
            walk(ch, held)

    walk(ctx.tree, frozenset())
    return findings


# --------------------------------------------------------------------- #
# GL003 — blocking call inside `async def`
# --------------------------------------------------------------------- #
# Motivation: serve's proxy/handle/multiplex and the OpenAI endpoint run
# on shared asyncio loops; one synchronous sleep or network call stalls
# EVERY in-flight request on that loop (and the local-mode loop guard in
# serve/local_mode.py exists for exactly this failure class).

_GL003_BANNED = {
    "time.sleep": "time.sleep() stalls the event loop; use "
                  "asyncio.sleep()",
    "sleep": "time.sleep() stalls the event loop; use asyncio.sleep()",
    "subprocess.run": "subprocess.run() blocks the loop",
    "subprocess.call": "subprocess.call() blocks the loop",
    "subprocess.check_call": "subprocess.check_call() blocks the loop",
    "subprocess.check_output": "subprocess.check_output() blocks the loop",
    "os.system": "os.system() blocks the loop",
    "urllib.request.urlopen": "urlopen() does blocking I/O on the loop",
    "urlopen": "urlopen() does blocking I/O on the loop",
    "socket.create_connection": "blocking connect on the loop",
}


@file_rule("GL003")
def check_blocking_in_async(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []

    def scan_async(fn: ast.AsyncFunctionDef):
        def walk(node: ast.AST, awaited: bool = False):
            if _is_funcdef(node):
                # nested sync defs may run in an executor; nested ASYNC
                # defs get their own scan from the module walk below
                # (descending here double-reported every finding)
                return
            if isinstance(node, ast.Await):
                walk(node.value, awaited=True)
                return
            if isinstance(node, ast.Call) and not awaited:
                d = dotted(node.func)
                msg = _GL003_BANNED.get(d)
                if msg is None and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "join" and not node.args \
                        and not node.keywords:
                    msg = ".join() blocks the event loop"
                if msg:
                    findings.append(Finding(
                        "GL003", ctx.relpath, node.lineno,
                        node.col_offset,
                        f"{msg} (inside async def {fn.name})"))
            for ch in ast.iter_child_nodes(node):
                walk(ch)
        for stmt in fn.body:
            walk(stmt)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            scan_async(node)
    return findings


# --------------------------------------------------------------------- #
# GL004 — O(n) list ops on hot queues
# --------------------------------------------------------------------- #
# Motivation: PR 2 swept the engine/handle/worker hot queues onto
# collections.deque after list.pop(0) showed up in profiles; this keeps
# the stragglers (and future reintroductions) out. sys.path-style
# prepends are exempt — they are rare, tiny, and order-semantic.

@file_rule("GL004")
def check_hot_queue_ops(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        meth = node.func.attr
        if meth not in ("pop", "insert") or not node.args:
            continue
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Constant) and arg0.value == 0):
            continue
        if meth == "pop" and len(node.args) != 1:
            continue
        if meth == "insert" and len(node.args) != 2:
            continue
        recv = dotted(node.func.value)
        seg = _last_segment(recv).lower() if recv else ""
        if seg in ("path", "paths") or seg.endswith("path") \
                or seg.endswith("paths"):
            continue  # sys.path.insert(0, ...) and friends
        findings.append(Finding(
            "GL004", ctx.relpath, node.lineno, node.col_offset,
            f"{seg or 'list'}.{meth}(0{', ...' if meth == 'insert' else ''}"
            f") is O(n); use collections.deque "
            f"({'popleft' if meth == 'pop' else 'appendleft'})"))
    return findings


# --------------------------------------------------------------------- #
# GL005 — import hygiene (static counterpart of test_no_heavy_imports)
# --------------------------------------------------------------------- #
# Motivation: worker fork/startup cost is dominated by imports (jax alone
# is hundreds of ms); `import ray_tpu` must stay light. The dynamic test
# catches a leak only at runtime — this walks the STATIC top-level import
# closure of ray_tpu/__init__ and flags any heavy import inside it, with
# the exact file:line to fix.

HEAVY_MODULES = {"jax", "jaxlib", "flax", "optax", "aiohttp",
                 "opentelemetry", "torch", "tensorflow", "pandas",
                 "scipy", "sklearn"}
IMPORT_ROOT = "ray_tpu"


def _module_name(relpath: str) -> Optional[str]:
    if not relpath.endswith(".py"):
        return None
    parts = relpath[:-3].replace(os.sep, "/").split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def _is_type_checking_if(node: ast.If) -> bool:
    d = dotted(node.test)
    return d in ("TYPE_CHECKING", "typing.TYPE_CHECKING")


def _top_level_imports(tree: ast.Module):
    """Yield Import/ImportFrom nodes that execute at module import time
    (including inside top-level try/if, excluding `if TYPE_CHECKING`)."""
    def scan(body):
        for node in body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                yield node
            elif isinstance(node, ast.Try):
                yield from scan(node.body)
                for h in node.handlers:
                    yield from scan(h.body)
                yield from scan(node.orelse)
                yield from scan(node.finalbody)
            elif isinstance(node, ast.If) and not _is_type_checking_if(node):
                yield from scan(node.body)
                yield from scan(node.orelse)
    yield from scan(tree.body)


@project_rule("GL005")
def check_import_hygiene(ctxs: dict[str, FileContext]) -> Iterable[Finding]:
    modules: dict[str, FileContext] = {}
    for rel, ctx in ctxs.items():
        name = _module_name(rel)
        if name and (name == IMPORT_ROOT
                     or name.startswith(IMPORT_ROOT + ".")):
            modules[name] = ctx
    if IMPORT_ROOT not in modules:
        return []

    def deps_of(name: str, ctx: FileContext) -> set[str]:
        deps: set[str] = set()

        def add(target: str):
            # importing a.b.c imports a and a.b too (__init__ chain)
            parts = target.split(".")
            for i in range(1, len(parts) + 1):
                cand = ".".join(parts[:i])
                if cand in modules:
                    deps.add(cand)

        pkg = name if modules[name].relpath.endswith("__init__.py") \
            else name.rsplit(".", 1)[0]
        for node in _top_level_imports(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    add(alias.name)
            else:
                if node.level:
                    base_parts = pkg.split(".")
                    up = node.level - 1
                    if up:
                        base_parts = base_parts[:-up] if up < len(
                            base_parts) else []
                    base = ".".join(base_parts)
                else:
                    base = ""
                mod = (base + "." + node.module if base and node.module
                       else (node.module or base))
                if mod:
                    add(mod)
                    for alias in node.names:
                        add(mod + "." + alias.name)
        return deps

    # BFS the import closure from the package root
    closure: set[str] = set()
    frontier = [IMPORT_ROOT]
    while frontier:
        name = frontier.pop()
        if name in closure:
            continue
        closure.add(name)
        frontier.extend(deps_of(name, modules[name]) - closure)

    findings: list[Finding] = []
    for name in sorted(closure):
        ctx = modules[name]
        for node in _top_level_imports(ctx.tree):
            roots = []
            if isinstance(node, ast.Import):
                roots = [a.name.split(".")[0] for a in node.names]
            elif node.level == 0 and node.module:
                roots = [node.module.split(".")[0]]
            for r in roots:
                if r in HEAVY_MODULES:
                    findings.append(Finding(
                        "GL005", ctx.relpath, node.lineno,
                        node.col_offset,
                        f"top-level `import {r}` in a module on the "
                        f"eager `import {IMPORT_ROOT}` path; import it "
                        f"lazily inside the function that needs it"))
    return findings


# --------------------------------------------------------------------- #
# GL006 — control-plane frame parity, pinned to PROTOCOL_VERSION
# --------------------------------------------------------------------- #
# Motivation: every `{"t": ...}` frame a peer sends must have a handler
# on the receiving side — a handler-less frame type is silently dropped
# (or worse, poisons a batch). The full frame inventory is additionally
# pinned to PROTOCOL_VERSION via frames.json: changing the wire
# vocabulary without bumping the version (protocol.py's contract) is
# itself a finding. Regenerate the manifest with --update-frames.

FRAME_MODULES = (
    "ray_tpu/core/worker.py",
    "ray_tpu/core/client.py",
    "ray_tpu/core/runtime.py",
    "ray_tpu/core/node_agent.py",
    "ray_tpu/core/flight.py",       # pull_reply builds the flight_ring frame
    "ray_tpu/core/stacks.py",       # dump_reply builds the stack_reply frame
    "ray_tpu/core/directory.py",    # dir_update/dir_query senders (v7)
    "ray_tpu/util/metrics.py",
    "ray_tpu/util/tracing.py",
    "ray_tpu/util/chaos.py",
    "ray_tpu/experimental/device_objects.py",
)
PROTOCOL_FILE = "ray_tpu/core/protocol.py"
FRAMES_MANIFEST = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "frames.json")


def _t_ish(node: ast.AST) -> bool:
    """Does this expression read a frame's type tag? t / msg["t"] /
    m.get("t") / reply.get("t")."""
    if isinstance(node, ast.Name) and node.id == "t":
        return True
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return isinstance(sl, ast.Constant) and sl.value == "t"
    if isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr == "get" and node.args:
        a0 = node.args[0]
        return isinstance(a0, ast.Constant) and a0.value == "t"
    return False


def _collect_frames(ctx: FileContext):
    """-> (sent {type: (line)}, handled {type: line})."""
    sent: dict[str, int] = {}
    handled: dict[str, int] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and k.value == "t" and \
                        isinstance(v, ast.Constant) and \
                        isinstance(v.value, str):
                    sent.setdefault(v.value, node.lineno)
        elif isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(_t_ish(s) for s in sides):
                continue
            for s in sides:
                if isinstance(s, ast.Constant) and isinstance(s.value, str):
                    handled.setdefault(s.value, node.lineno)
                elif isinstance(s, (ast.Tuple, ast.List, ast.Set)):
                    for el in s.elts:
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            handled.setdefault(el.value, node.lineno)
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.value, ast.Dict) and _t_ish(node.slice):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    handled.setdefault(k.value, node.lineno)
    return sent, handled


def _protocol_version(ctx: FileContext) -> Optional[int]:
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id == "PROTOCOL_VERSION" and \
                isinstance(node.value, ast.Constant):
            return node.value.value
    return None


def compute_frame_inventory(ctxs: dict[str, FileContext]):
    sent: dict[str, tuple[str, int]] = {}
    handled: dict[str, tuple[str, int]] = {}
    for rel in FRAME_MODULES:
        ctx = ctxs.get(rel)
        if ctx is None:
            continue
        s, h = _collect_frames(ctx)
        for ty, line in s.items():
            sent.setdefault(ty, (rel, line))
        for ty, line in h.items():
            handled.setdefault(ty, (rel, line))
    return sent, handled


@project_rule("GL006")
def check_frame_parity(ctxs: dict[str, FileContext]) -> Iterable[Finding]:
    present = [rel for rel in FRAME_MODULES if rel in ctxs]
    if len(present) < len(FRAME_MODULES):
        return []  # partial-tree lint (unit tests, single files)
    sent, handled = compute_frame_inventory(ctxs)
    findings: list[Finding] = []
    for ty in sorted(set(sent) - set(handled)):
        rel, line = sent[ty]
        findings.append(Finding(
            "GL006", rel, line, 0,
            f'frame type "{ty}" is sent but no peer handles it '
            f"(silently dropped on receive)"))
    for ty in sorted(set(handled) - set(sent)):
        rel, line = handled[ty]
        findings.append(Finding(
            "GL006", rel, line, 0,
            f'frame type "{ty}" has a handler but no sender '
            f"(dead handler, or the sender bypasses the scanned "
            f"modules)"))

    # version pinning
    pctx = ctxs.get(PROTOCOL_FILE)
    pv = _protocol_version(pctx) if pctx else None
    frames = sorted(set(sent) | set(handled))
    if pv is not None:
        if not os.path.exists(FRAMES_MANIFEST):
            findings.append(Finding(
                "GL006", PROTOCOL_FILE, 1, 0,
                "frame manifest missing; run `python -m tools.graftlint "
                "--update-frames`"))
        else:
            with open(FRAMES_MANIFEST) as f:
                manifest = json.load(f)
            if manifest.get("frames") != frames:
                if manifest.get("protocol_version") == pv:
                    findings.append(Finding(
                        "GL006", PROTOCOL_FILE, 1, 0,
                        f"wire frame inventory changed but "
                        f"PROTOCOL_VERSION is still {pv}; bump it "
                        f"(core/protocol.py contract) and run "
                        f"`python -m tools.graftlint --update-frames`"))
                else:
                    findings.append(Finding(
                        "GL006", PROTOCOL_FILE, 1, 0,
                        f"PROTOCOL_VERSION is {pv} but the frame "
                        f"manifest was pinned at "
                        f"{manifest.get('protocol_version')}; run "
                        f"`python -m tools.graftlint --update-frames`"))
    return findings


def update_frames_manifest(ctxs: dict[str, FileContext]) -> dict:
    missing = [rel for rel in FRAME_MODULES + (PROTOCOL_FILE,)
               if rel not in ctxs]
    if missing:
        # re-pinning from a subtree would silently shrink the manifest
        # to a partial inventory and break the GL006 gate for everyone
        raise FileNotFoundError(
            "--update-frames needs the full tree (run it over ray_tpu/); "
            "missing: " + ", ".join(missing))
    sent, handled = compute_frame_inventory(ctxs)
    pctx = ctxs.get(PROTOCOL_FILE)
    pv = _protocol_version(pctx) if pctx else None
    manifest = {"protocol_version": pv,
                "frames": sorted(set(sent) | set(handled))}
    with open(FRAMES_MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1)
        f.write("\n")
    return manifest


# --------------------------------------------------------------------- #
# GL007 — metric naming + once-only registration
# --------------------------------------------------------------------- #
# Motivation: the head merges every process's series by NAME; names
# outside the rtpu_(core|llm|serve|rl|data|obs)_ namespaces silently
# fall off the dashboards and the metrics_summary() aggregations.
# Constructing a Metric per call re-validates against the registry on a
# hot path — construct at module scope or through cached_metric
# (llm/telemetry.py's pattern).

_METRIC_CTORS = ("Counter", "Gauge", "Histogram")
_METRIC_NAME_RE = re.compile(
    r"^rtpu_(core|llm|serve|rl|data|obs)_[a-z0-9_]+$")
_GL007_EXEMPT_FILES = ("ray_tpu/util/metrics.py",)


def _metric_name_arg(node: ast.Call) -> Optional[ast.Constant]:
    fn = _last_segment(dotted(node.func))
    idx = None
    if fn in _METRIC_CTORS:
        idx = 0
    elif fn == "cached_metric":
        idx = 1
    elif fn and any(s in fn.lower()
                    for s in ("metric", "hist", "gauge", "counter")):
        idx = 0
    if idx is None:
        return None
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return kw.value
    if len(node.args) > idx and isinstance(node.args[idx], ast.Constant):
        return node.args[idx]
    return None


@file_rule("GL007")
def check_metric_conventions(ctx: FileContext) -> Iterable[Finding]:
    if ctx.relpath in _GL007_EXEMPT_FILES:
        return []
    findings: list[Finding] = []

    # which Call nodes sit inside a function body?
    in_func: set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for ch in ast.walk(node):
                if isinstance(ch, ast.Call):
                    in_func.add(id(ch))

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = _last_segment(dotted(node.func))
        name_node = _metric_name_arg(node)
        if name_node is not None and isinstance(name_node.value, str):
            name = name_node.value
            strict = fn in _METRIC_CTORS or fn == "cached_metric"
            if not _METRIC_NAME_RE.match(name) and (
                    strict or name.startswith("rtpu_")):
                findings.append(Finding(
                    "GL007", ctx.relpath, node.lineno, node.col_offset,
                    f'metric name "{name}" does not match '
                    f"rtpu_(core|llm|serve|rl|data|obs)_[a-z0-9_]+"))
        if fn in _METRIC_CTORS and id(node) in in_func:
            findings.append(Finding(
                "GL007", ctx.relpath, node.lineno, node.col_offset,
                f"{fn}(...) constructed inside a function (per-call "
                f"re-registration); construct at module scope or via "
                f"cached_metric()"))
    return findings


# --------------------------------------------------------------------- #
# GL008 — swallowed exceptions
# --------------------------------------------------------------------- #
# Motivation: daemon threads (recv loops, drop loops, flushers) and
# actor loops die silently on an uncaught exception — and live wrongly
# on an over-caught one. A bare `except:` eats KeyboardInterrupt/
# SystemExit (it has stranded worker teardown before); a broad
# `except Exception: pass` with no comment hides bugs from the one
# person who will ever see them: the reader.

_BROAD = ("Exception", "BaseException")


def _is_silent_body(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if isinstance(stmt, ast.Return) and (
                stmt.value is None
                or (isinstance(stmt.value, ast.Constant)
                    and stmt.value.value is None)):
            continue
        return False
    return True


def _handler_types(node: ast.ExceptHandler) -> list[str]:
    if node.type is None:
        return []
    elts = node.type.elts if isinstance(node.type, ast.Tuple) \
        else [node.type]
    return [_last_segment(dotted(e)) or "?" for e in elts]


# --------------------------------------------------------------------- #
# GL009 — short-slice seal polling where event-driven waits exist
# --------------------------------------------------------------------- #
# Motivation: the native store exposes event-driven seal notification
# (os_wait_sealed multi-oid waits, os_chan_get stop-aware blocking get,
# os_wait_seq) — a futex wake delivers a completion the instant it seals.
# A `while` loop re-issuing `store.get(..., timeout_ms=<short>)` slices,
# or sleeping briefly between `contains()` probes, burns a syscall + GIL
# round-trip per slice and adds up to a slice of latency per message;
# the compiled-DAG channel transport was rebuilt precisely to retire
# this pattern. Long slices (>150ms) that exist to re-check out-of-band
# state (spill files, directory entries, reconnect-swapped stores) are
# NOT flagged — they are the documented fallback cadence, with the futex
# still delivering the fast path.

_GL009_MAX_SLICE_MS = 150
_GL009_MAX_SLEEP_S = 0.25


def _const_num(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)):
        return node.value
    return None


@file_rule("GL009")
def check_seal_polling(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    def loop_calls(loop: ast.While) -> list[ast.Call]:
        """Call nodes executed BY the loop body: nested function/lambda
        bodies run elsewhere, so recurse without descending into them
        (ast.walk can't prune, it flattens everything)."""
        out: list[ast.Call] = []

        def visit(node: ast.AST):
            for child in ast.iter_child_nodes(node):
                if _is_funcdef(child):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                visit(child)

        visit(loop)
        return out

    for loop in ast.walk(ctx.tree):
        if not isinstance(loop, ast.While):
            continue
        calls = loop_calls(loop)
        has_contains = any(
            isinstance(c.func, ast.Attribute) and c.func.attr == "contains"
            for c in calls)
        for c in calls:
            if not isinstance(c.func, ast.Attribute):
                continue
            meth = c.func.attr
            if meth == "get":
                for kw in c.keywords:
                    if kw.arg != "timeout_ms":
                        continue
                    v = _const_num(kw.value)
                    if v is not None and 0 < v <= _GL009_MAX_SLICE_MS:
                        findings.append(Finding(
                            "GL009", ctx.relpath, c.lineno, c.col_offset,
                            f"{meth}(timeout_ms={v:g}) retry slice inside "
                            f"a while loop polls for a seal; use "
                            f"wait_sealed / get_chan (futex wakes on "
                            f"seal) and keep only long re-check slices"))
            elif meth == "sleep" and has_contains and c.args:
                v = _const_num(c.args[0])
                if v is not None and 0 < v <= _GL009_MAX_SLEEP_S:
                    findings.append(Finding(
                        "GL009", ctx.relpath, c.lineno, c.col_offset,
                        f"sleep({v:g}) between contains() probes polls "
                        f"for a seal; use wait_sealed (futex wakes on "
                        f"seal) instead of a sleep-probe loop"))
    return findings


# --------------------------------------------------------------------- #
# GL010 — eager formatting/allocation at flight-recorder emit sites
# --------------------------------------------------------------------- #
# Motivation: flight.evt() is budgeted at well under a microsecond so it
# can stay ALWAYS-ON inside the zero-dispatch fast paths (core/flight.py
# docstring). Python evaluates arguments BEFORE the call, so an f-string,
# %-format, .format(), str()/repr() or a dict/list/set literal in evt's
# argument list pays allocation + formatting on every emit even though
# the recorder only stores fixed-width ints — exactly the cost the
# struct-packed ring exists to avoid. Codes resolve to names at export
# time; object ids compress through flight.lo48 (bytes slicing, no
# string rendering).

_GL010_STR_BUILDERS = ("str", "repr", "bytes", "hex", "format")


def _gl010_bad_arg(arg: ast.AST) -> Optional[str]:
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod) and (
            isinstance(arg.left, ast.Constant)
            and isinstance(arg.left.value, str)):
        return "%-formatting"
    if isinstance(arg, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                        ast.ListComp, ast.SetComp, ast.GeneratorExp)):
        return "container literal"
    if isinstance(arg, ast.Call):
        if isinstance(arg.func, ast.Attribute) and \
                arg.func.attr == "format":
            return ".format() call"
        if isinstance(arg.func, ast.Name) and \
                arg.func.id in _GL010_STR_BUILDERS:
            return f"{arg.func.id}() call"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return "string constant (the ring stores ints; add a code)"
    return None


@file_rule("GL010")
def check_flight_emit_cost(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else None)
        if name != "evt":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            why = _gl010_bad_arg(arg)
            if why:
                findings.append(Finding(
                    "GL010", ctx.relpath, arg.lineno, arg.col_offset,
                    f"{why} evaluated on the flight-recorder hot path; "
                    f"evt() args must be plain ints (codes + "
                    f"flight.lo48 ids) — formatting belongs at export "
                    f"time"))
    return findings


# --------------------------------------------------------------------- #
# GL011 — unbounded request-controlled TSDB/metric label values
# --------------------------------------------------------------------- #
# Motivation: the metrics plane (ray_tpu/obs) retains one preallocated
# ring PER (name, label-set) series. The TSDB's hard cardinality cap
# folds overflow into an __overflow__ sink, so memory is safe — but a
# record site that mints label values by FORMATTING request-controlled
# data (f"tenant-{tid}", str(request_id), "%s" % route) fills the whole
# series table with one-sample garbage and evicts the real series into
# the sink: the history silently goes blind. Label values must come
# from bounded vocabularies (the admission gate's bucket(), fixed
# enums, config) — bounding belongs at the call site that OWNS the
# vocabulary, not in the store. Flagged: f-string / str()-family /
# %-format / .format() / string-concat VALUES inside a `tags=` dict at
# metric record sites (.inc/.set/.observe) and inside the key tuple of
# TSDB .record() calls. Plain variables pass — the rule catches the
# syntactic act of minting a fresh string per record, which is exactly
# the unbounded case.

_GL011_RECORD_METHODS = ("inc", "set", "observe")


def _gl011_bad_value(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.JoinedStr):
        return "f-string"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod) and \
            (isinstance(node.left, ast.JoinedStr) or
             (isinstance(node.left, ast.Constant) and
              isinstance(node.left.value, str))):
        # only string % value is formatting; integer modulo (n % 4) is
        # the bounded-bucketing pattern this rule RECOMMENDS
        return "%-formatting"
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        # "pfx" + x (or x + "sfx"): minting a fresh string per record
        if any(isinstance(s, ast.Constant) and isinstance(s.value, str)
               for s in (node.left, node.right)):
            return "string concatenation"
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "format":
            return ".format() call"
        if isinstance(node.func, ast.Name) and \
                node.func.id in _GL010_STR_BUILDERS:
            return f"{node.func.id}() call"
    return None


def _gl011_scan_dict(d: ast.Dict) -> Iterable[tuple[ast.AST, str]]:
    for v in d.values:
        why = _gl011_bad_value(v)
        if why:
            yield v, why


@file_rule("GL011")
def check_unbounded_metric_labels(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        meth = node.func.attr
        if meth in _GL011_RECORD_METHODS:
            for kw in node.keywords:
                if kw.arg != "tags" or not isinstance(kw.value, ast.Dict):
                    continue
                for v, why in _gl011_scan_dict(kw.value):
                    findings.append(Finding(
                        "GL011", ctx.relpath, v.lineno, v.col_offset,
                        f"{why} mints a label value at a metric record "
                        f"site — one fresh string per record grows a "
                        f"TSDB series each; bound the vocabulary at "
                        f"the call site (bucket()/enum/config) before "
                        f"tagging"))
        elif meth == "record":
            # TSDB.record(name, kind, key, ts, value): the key tuple's
            # (k, v) pairs are the label set
            if len(node.args) < 3 or not isinstance(
                    node.args[2], (ast.Tuple, ast.List)):
                continue
            for pair in node.args[2].elts:
                if not isinstance(pair, (ast.Tuple, ast.List)) or \
                        len(pair.elts) != 2:
                    continue
                why = _gl011_bad_value(pair.elts[1])
                if why:
                    findings.append(Finding(
                        "GL011", ctx.relpath, pair.elts[1].lineno,
                        pair.elts[1].col_offset,
                        f"{why} mints a TSDB label value at a "
                        f".record() site — unbounded label sets evict "
                        f"real series into the __overflow__ sink; "
                        f"bound the vocabulary before recording"))
    return findings


@file_rule("GL008")
def check_swallowed_exceptions(ctx: FileContext) -> Iterable[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            findings.append(Finding(
                "GL008", ctx.relpath, node.lineno, node.col_offset,
                "bare `except:` catches KeyboardInterrupt/SystemExit; "
                "use `except Exception` (with a comment) or narrower"))
            continue
        types = _handler_types(node)
        if not any(t in _BROAD for t in types):
            continue
        if not _is_silent_body(node.body):
            continue
        end = max(getattr(s, "end_lineno", s.lineno) for s in node.body)
        has_comment = any(ctx.comment_on(i)
                          for i in range(node.lineno, end + 1))
        if not has_comment:
            findings.append(Finding(
                "GL008", ctx.relpath, node.lineno, node.col_offset,
                f"broad `except {'/'.join(types)}` silently swallowed; "
                f"add a `# why` comment or handle/narrow it"))
    return findings
